"""Pipeline (PP) and Mixture-of-Experts (EP) tests.

Both strategies are absent from the reference (SURVEY.md §2.5); these tests
pin their correctness: the SPMD pipeline must equal sequential layer
application, and sharded experts must equal local experts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.parallel import moe
from mpi_operator_tpu.parallel.pipeline import run_pipeline
from mpi_operator_tpu.runtime import MeshPlan, build_mesh
from mpi_operator_tpu.runtime.topology import AXIS_DATA, AXIS_EXPERT, AXIS_PIPE

# slow tier: XLA compiles / subprocess gangs (see pytest.ini)
pytestmark = pytest.mark.slow


# ---------- pipeline ----------


def _stage_fn(p, x):
    # one "layer": affine + nonlinearity
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_params(key, n_layers, d):
    ks = jax.random.split(key, n_layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (d, d)) * 0.5 for k in ks]),
        "b": jnp.zeros((n_layers, d)),
    }


def _sequential(params, x, n_layers):
    for i in range(n_layers):
        x = _stage_fn(jax.tree.map(lambda a: a[i], params), x)
    return x


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    mesh = build_mesh(MeshPlan(axes={AXIS_DATA: 2, AXIS_PIPE: 4}))
    n_layers, d, b = 8, 16, 16
    params = _stacked_params(jax.random.PRNGKey(0), n_layers, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d))
    want = _sequential(params, x, n_layers)
    got = jax.jit(
        lambda p, xx: run_pipeline(
            _stage_fn, p, xx, mesh, n_microbatches=n_micro
        )
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_pipeline_no_pipe_axis_falls_back():
    mesh = build_mesh(MeshPlan(axes={AXIS_DATA: 8}))
    n_layers, d = 4, 8
    params = _stacked_params(jax.random.PRNGKey(0), n_layers, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d))
    want = _sequential(params, x, n_layers)
    got = run_pipeline(_stage_fn, params, x, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


# ---------- moe ----------


@pytest.fixture(scope="module")
def moe_setup():
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=8, capacity_factor=2.0)
    params = moe.init(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))
    return cfg, params, x


def test_moe_local_shapes_and_aux(moe_setup):
    cfg, params, x = moe_setup
    y, aux = moe.apply(cfg, params, x)
    assert y.shape == x.shape
    assert jnp.isfinite(aux)
    # perfectly balanced load-balance loss is 1.0; any routing is >= 1
    assert float(aux) >= 0.99


def test_moe_sharded_matches_local(moe_setup):
    cfg, params, x = moe_setup
    y_local, aux_local = moe.apply(cfg, params, x)
    mesh = build_mesh(MeshPlan(axes={AXIS_EXPERT: 8}))
    y_shard, aux_shard = jax.jit(
        lambda p, xx: moe.apply(cfg, p, xx, mesh=mesh)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(y_local, np.float32), np.asarray(y_shard, np.float32),
        atol=2e-2, rtol=2e-2,
    )
    np.testing.assert_allclose(float(aux_local), float(aux_shard), rtol=1e-5)


def test_moe_gradients_flow(moe_setup):
    cfg, params, x = moe_setup

    def loss(p):
        y, aux = moe.apply(cfg, p, x)
        return jnp.mean(y**2) + 0.01 * aux

    g = jax.grad(loss)(params)
    gnorm = sum(float(jnp.sum(jnp.abs(l))) for l in jax.tree.leaves(g))
    assert np.isfinite(gnorm) and gnorm > 0
    # router gets gradient through the gate
    assert float(jnp.sum(jnp.abs(g["router"]["w"]))) > 0


def test_moe_capacity_drops_tokens():
    cfg = moe.MoEConfig(d_model=8, d_ff=16, n_experts=2, capacity_factor=0.1)
    params = moe.init(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 8))
    y, _ = moe.apply(cfg, params, x)
    # capacity 1 per expert → most tokens dropped → mostly zero rows
    zero_rows = np.sum(np.all(np.asarray(y[0]) == 0, axis=-1))
    assert zero_rows >= 28
