"""Node agent: the per-node execution plane (the kubelet role).

Round-3's verdict: the cluster overlay could admit and place jobs that
nothing could execute — the only executor ran every pod on the leader.
These tests pin the new execution plane end to end:

- the scalar-mode gang scheduler binds to live registered Nodes (spread,
  capacity-checked, all-or-nothing) the moment agents register;
- the NodeMonitor evicts pods off nodes whose heartbeat stops (≙ the kube
  node controller's eviction, which the reference's worker-loss recovery
  silently depends on);
- two NodeAgents sharing a store each execute exactly the pods bound to
  their identity, stamp fetchable log URLs, and the whole flow survives an
  agent being killed mid-job (gang restarts on the surviving node).
"""

import os

import pytest
import time
import urllib.request

from mpi_operator_tpu.api.types import Container, ObjectMeta
from mpi_operator_tpu.controller.node_monitor import NodeMonitor
from mpi_operator_tpu.machinery.events import EventRecorder
from mpi_operator_tpu.machinery.objects import (
    NODE_NAMESPACE,
    Node,
    Pod,
    PodPhase,
    PodSpec,
)
from mpi_operator_tpu.machinery.store import ObjectStore
from mpi_operator_tpu.scheduler.gang import LABEL_JOB_NAME, GangScheduler

from test_scheduler import bound_pods, finish, make_gang, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_node(store, name, *, chips=None, ready=True, hb=None, address="127.0.0.1"):
    node = Node()
    node.metadata.namespace = NODE_NAMESPACE
    node.metadata.name = name
    node.status.address = address
    node.status.ready = ready
    node.status.capacity_chips = chips
    node.status.last_heartbeat = time.time() if hb is None else hb
    return store.create(node)


# ---------------------------------------------------------------------------
# scheduler: scalar node mode
# ---------------------------------------------------------------------------


def test_gang_spreads_across_live_nodes():
    store = ObjectStore()
    sched = GangScheduler(store)
    make_node(store, "node-a")
    make_node(store, "node-b")
    make_gang(store, "j", min_member=2)
    for i in range(2):
        make_pod(store, "j", i)
    sched.sync()
    bound = {p.metadata.name: p.spec.node_name for p in bound_pods(store, "j")}
    # least-loaded spread, worker 0 first deterministically
    assert bound == {"j-worker-0": "node-a", "j-worker-1": "node-b"}


def test_gang_holds_until_node_capacity_frees():
    store = ObjectStore()
    sched = GangScheduler(store)
    make_node(store, "node-a", chips=2)
    # gang of two 2-chip pods: only one fits node-a → all-or-nothing holds
    make_gang(store, "j", min_member=2)
    for i in range(2):
        make_pod(store, "j", i, chips=2)
    sched.sync()
    assert bound_pods(store, "j") == []
    make_node(store, "node-b", chips=2)
    sched.sync()
    assert len(bound_pods(store, "j")) == 2


def test_stale_or_notready_nodes_are_not_targets():
    store = ObjectStore()
    sched = GangScheduler(store, node_grace=1.0)
    make_node(store, "node-dead", hb=time.time() - 30)
    make_node(store, "node-drained", ready=False)
    make_gang(store, "j", min_member=1)
    make_pod(store, "j", 0)
    sched.sync()
    assert bound_pods(store, "j") == []  # node mode, zero live targets: hold
    make_node(store, "node-live")
    sched.sync()
    assert [p.spec.node_name for p in bound_pods(store, "j")] == ["node-live"]


def test_require_nodes_holds_gang_until_first_agent_registers():
    """Operator-up/agents-not-yet window in a node-mode deployment
    (--executor none, the cluster/helm shape): a fresh gang must HOLD, not
    bind to the in-process 'local' sentinel no agent ever claims — admitted
    gangs are never re-placed, so that binding would wedge the job forever."""
    store = ObjectStore()
    sched = GangScheduler(store, require_nodes=True)
    make_gang(store, "j", min_member=2)
    for i in range(2):
        make_pod(store, "j", i)
    sched.sync()
    assert bound_pods(store, "j") == []  # held, not bound to 'local'
    make_node(store, "node-a")
    sched.sync()
    bound = bound_pods(store, "j")
    assert len(bound) == 2
    assert all(p.spec.node_name == "node-a" for p in bound)


def test_require_nodes_heals_local_sentinel_bindings():
    """PENDING pods bound to 'local' (pre-upgrade state, or a gang that
    slipped in while the operator ran without require_nodes): the scheduler
    unbinds and re-places them onto real nodes instead of leaving them
    wedged behind a binding nothing will ever claim."""
    store = ObjectStore()
    sched = GangScheduler(store, require_nodes=True)
    make_gang(store, "j", min_member=1)
    p = make_pod(store, "j", 0)
    p.spec.node_name = "local"
    store.update(p, force=True)
    make_node(store, "node-a")
    sched.sync()
    assert [q.spec.node_name for q in bound_pods(store, "j")] == ["node-a"]


def test_evict_pod_does_not_clobber_concurrent_success():
    """A reaper stamping Succeeded between evict_pod's snapshot and its
    write must win: the rv precondition on the eviction patch surfaces the
    race as Conflict, the guarded re-read sees the pod finished, and the
    eviction backs off — anything else would flip a completed pod into a
    retryable Failed and trigger a spurious gang restart."""
    from mpi_operator_tpu.machinery.objects import evict_pod

    store = ObjectStore()
    make_gang(store, "j", min_member=1)
    pod = make_pod(store, "j", 0)

    real_patch = store.patch
    raced = {"done": False}

    def racing_patch(kind, namespace, name, patch, **kw):
        if not raced["done"] and kind == "Pod":
            raced["done"] = True
            # the reaper lands Succeeded first — the evictor's snapshot
            # (and its rv precondition) is now stale
            cur = store.get("Pod", namespace, name)
            cur.status.phase = PodPhase.SUCCEEDED
            store.update(cur, force=True)
        return real_patch(kind, namespace, name, patch, **kw)

    store.patch = racing_patch
    assert evict_pod(store, pod, "node drained") is False
    cur = store.get("Pod", "default", pod.metadata.name)
    assert cur.status.phase == PodPhase.SUCCEEDED  # completion preserved


def test_log_endpoint_honors_tokens(tmp_path):
    """With tokens configured the agent's log endpoint 401s anonymous
    fetches and accepts either tier (admin or read) — training logs can
    contain data samples and deserve the same guard the store has.
    /healthz stays open for probes, and ctl's fetch helper presents the
    token end to end."""
    import urllib.error
    import urllib.request

    from mpi_operator_tpu.executor.agent import LogServer
    from mpi_operator_tpu.opshell.ctl import _read_log_from

    (tmp_path / "w.log").write_text("hello")
    srv = LogServer(str(tmp_path), host="127.0.0.1",
                    tokens=["adm1n", "v1ewer"]).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/logs/w.log"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=5)
        assert ei.value.code == 401
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5
        ) as r:
            assert r.status == 200  # probes carry no headers
        for tok in ("adm1n", "v1ewer"):
            req = urllib.request.Request(
                url, headers={"Authorization": f"Bearer {tok}"}
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.read() == b"hello"
            assert _read_log_from(url, 0, tok) == b"hello"
        with pytest.raises(OSError):
            _read_log_from(url, 0, "wr0ng")
    finally:
        srv.stop()


def test_inventory_mode_routes_around_dead_registered_nodes():
    """A dead slice host must not look free to the block search — a gang
    evicted off it would otherwise be re-placed there and bounce through
    evict/restart until backoffLimit fails the job."""
    from test_scheduler import make_topo_gang, nodes_of

    from mpi_operator_tpu.scheduler.inventory import SliceInventory

    store = ObjectStore()
    sched = GangScheduler(store, inventory=SliceInventory.parse("4"))
    # agents registered for hosts 0 and 1; host 0's agent is dead
    make_node(store, "slice0/0", hb=time.time() - 60)
    make_node(store, "slice0/1")
    make_topo_gang(store, sched, "a", (2,), 2)
    # the 2-host block skips the dead host 0: placed at offset 1 (hosts 1-2;
    # host 2 has no registered agent → stays schedulable, pure-inventory)
    assert nodes_of(store, "a") == ["slice0/1", "slice0/2"]


def test_fifo_capacity_released_to_next_gang_across_nodes():
    store = ObjectStore()
    sched = GangScheduler(store)
    make_node(store, "node-a", chips=1)
    make_node(store, "node-b", chips=1)
    make_gang(store, "first", min_member=2)
    for i in range(2):
        make_pod(store, "first", i)
    make_gang(store, "second", min_member=2)
    for i in range(2):
        make_pod(store, "second", i)
    sched.sync()
    assert len(bound_pods(store, "first")) == 2
    assert bound_pods(store, "second") == []  # full cluster: second waits
    finish(store, "first")
    sched.sync()
    assert len(bound_pods(store, "second")) == 2


# ---------------------------------------------------------------------------
# node monitor
# ---------------------------------------------------------------------------


def _bound_running_pod(store, job, node):
    pod = Pod(
        metadata=ObjectMeta(
            name=f"{job}-worker-0", namespace="default",
            labels={LABEL_JOB_NAME: job},
        ),
        spec=PodSpec(container=Container(), node_name=node),
    )
    pod.status.phase = PodPhase.RUNNING
    return store.create(pod)


def test_monitor_evicts_pods_off_stale_node():
    store = ObjectStore()
    rec = EventRecorder(store, component="test-monitor")
    make_node(store, "gone", hb=time.time() - 60)
    _bound_running_pod(store, "j", "gone")
    mon = NodeMonitor(store, rec, grace=5.0)
    mon.sync()
    node = store.get("Node", NODE_NAMESPACE, "gone")
    assert node.status.ready is False
    pod = store.get("Pod", "default", "j-worker-0")
    assert pod.status.phase == PodPhase.FAILED
    assert pod.is_evicted()  # reason=Evicted → controller treats as retryable
    events = [e for e in store.list("Event") if e.reason == "NodeLost"]
    assert events, "node loss must land in the audit trail"


def test_monitor_spares_fresh_and_static_nodes():
    store = ObjectStore()
    make_node(store, "fresh")
    make_node(store, "static", hb=0)  # manually registered: no hb contract
    _bound_running_pod(store, "a", "fresh")
    _bound_running_pod(store, "b", "static")
    mon = NodeMonitor(store, grace=5.0)
    mon.sync()
    assert store.get("Pod", "default", "a-worker-0").status.phase == PodPhase.RUNNING
    assert store.get("Pod", "default", "b-worker-0").status.phase == PodPhase.RUNNING
    assert store.get("Node", NODE_NAMESPACE, "static").status.ready is True


# ---------------------------------------------------------------------------
# agents: claim-by-identity, logs over HTTP (in-process stack)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # full stack / subprocess e2e
def test_two_agents_execute_one_pod_each_with_log_urls(tmp_path):
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.api.conditions import is_finished, is_succeeded
    from mpi_operator_tpu.controller.controller import (
        ControllerOptions,
        TPUJobController,
    )
    from mpi_operator_tpu.executor.agent import NodeAgent
    from mpi_operator_tpu.scheduler import GangScheduler

    store = ObjectStore()
    recorder = EventRecorder(store)
    controller = TPUJobController(store, recorder, ControllerOptions())
    scheduler = GangScheduler(store, recorder)
    agents = [
        NodeAgent(
            store, f"agent-{x}", logs_dir=str(tmp_path / x), workdir=REPO,
            heartbeat_interval=0.5,
        )
        for x in ("a", "b")
    ]
    client = TPUJobClient(store)
    controller.run()
    scheduler.start()
    for a in agents:
        a.start()
    try:
        client.create({
            "apiVersion": "tpujob.dev/v1",
            "kind": "TPUJob",
            "metadata": {"name": "hello"},
            "spec": {
                "worker": {
                    "replicas": 2,
                    "template": {"containers": [{
                        "name": "w", "image": "local",
                        "command": [
                            "python", "-c",
                            "import os; print('hi from host '"
                            " + os.environ['TPUJOB_HOST_ID'])",
                        ],
                    }]},
                },
                "slice": {"accelerator": "cpu", "chipsPerHost": 1},
            },
        })
        final = client.wait("hello", until=is_finished, timeout=60)
        assert is_succeeded(final.status), final.status.conditions
        # exactly one pod's log landed in each agent's directory
        for x in ("a", "b"):
            files = [f for f in os.listdir(tmp_path / x) if f.endswith(".log")]
            assert len(files) == 1, (x, files)
        # the stamped log path is a URL, fetchable from anywhere
        pods = store.list("Pod", "default", selector={LABEL_JOB_NAME: "hello"})
        assert len(pods) == 2
        for pod in pods:
            assert pod.status.log_path.startswith("http://"), pod.status.log_path
            with urllib.request.urlopen(pod.status.log_path, timeout=5) as r:
                body = r.read().decode()
            idx = pod.metadata.name.rsplit("-", 1)[1]
            assert f"hi from host {idx}" in body
    finally:
        for a in agents:
            a.stop()
        scheduler.stop()
        controller.stop()


# ---------------------------------------------------------------------------
# e2e: real process split (store server + operator + two agent processes)
# ---------------------------------------------------------------------------


def _wait_http(url, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            urllib.request.urlopen(url, timeout=2)
            return
        except OSError:
            time.sleep(0.3)
    raise TimeoutError(f"{url} never came up")


def _spawn(tmp_path, tag, argv):
    import subprocess

    logf = open(tmp_path / f"{tag}.log", "w+")
    proc = subprocess.Popen(
        argv, cwd=REPO, stdout=logf, stderr=subprocess.STDOUT, text=True
    )
    return proc, logf


def _reap(procs):
    for proc, logf in procs:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()
        logf.close()


def _proc_logs(tmp_path, tags):
    out = []
    for tag in tags:
        p = tmp_path / f"{tag}.log"
        if p.exists():
            out.append(f"--- {tag} ---\n" + p.read_text())
    return "\n".join(out)


def _start_cluster(tmp_path, *, node_grace=None, heartbeat=0.5,
                   ckpt_dir=None, preemption_grace=None, agent_chips=None,
                   eviction_grace=None):
    """store-serving operator (no local executor) + two agent processes.
    ``ckpt_dir`` emulates the shared checkpoint volume of a real cluster:
    both agents advertise the same path via --ckpt-dir (≙ one PVC mounted
    at the same mountPath on every node)."""
    import sys

    from mpi_operator_tpu.runtime.emulation import free_port

    port = free_port()
    procs = []
    op_flags = [
        sys.executable, "-m", "mpi_operator_tpu.opshell",
        "--store", f"sqlite:{tmp_path / 'store.db'}",
        "--serve-store", f"127.0.0.1:{port}",
        "--monitoring-port", "0",
    ]
    if node_grace is not None:
        op_flags += ["--node-grace", str(node_grace)]
    if preemption_grace is not None:
        op_flags += ["--preemption-grace", str(preemption_grace)]
    procs.append(_spawn(tmp_path, "operator", op_flags))
    _wait_http(f"http://127.0.0.1:{port}/healthz")
    for x in ("a", "b"):
        (tmp_path / f"logs-{x}").mkdir()
        agent_flags = [
            sys.executable, "-m", "mpi_operator_tpu.executor.agent",
            "--store", f"http://127.0.0.1:{port}",
            "--node-name", f"agent-{x}",
            "--logs-dir", str(tmp_path / f"logs-{x}"),
            "--workdir", REPO,
            "--heartbeat", str(heartbeat),
        ]
        if ckpt_dir is not None:
            agent_flags += ["--ckpt-dir", str(ckpt_dir)]
        if agent_chips is not None:
            agent_flags += ["--chips", str(agent_chips)]
        if eviction_grace is not None:
            agent_flags += ["--eviction-grace", str(eviction_grace)]
        procs.append(_spawn(tmp_path, f"agent-{x}", agent_flags))
    return port, procs


def _wait_nodes_registered(store, names, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        have = {n.metadata.name for n in store.list("Node", NODE_NAMESPACE)
                if n.status.ready}
        if set(names) <= have:
            return
        time.sleep(0.3)
    raise TimeoutError(f"nodes {names} never registered (have {have})")


@pytest.mark.slow  # full stack / subprocess e2e
def test_multinode_agents_run_pi_end_to_end(tmp_path):
    """The round-3 hole, closed: a store-serving operator that executes
    nothing itself + two separate agent processes. The 2-worker pi job's
    pods land one per agent (scheduler spread), the SPMD rendezvous crosses
    the process boundary via store-resolved coordinator addressing, and
    `ctl logs` reads the remote coordinator's output through the agent's
    log URL — no shared log filesystem assumed."""
    import subprocess
    import sys

    from mpi_operator_tpu.machinery.http_store import HttpStoreClient

    port, procs = _start_cluster(tmp_path)
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["agent-a", "agent-b"])
        submit = subprocess.run(
            [sys.executable, "examples/submit_job.py", f"http://127.0.0.1:{port}"],
            cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        detail = (submit.stdout + submit.stderr + "\n"
                  + _proc_logs(tmp_path, ["operator", "agent-a", "agent-b"]))
        assert submit.returncode == 0, detail
        assert "SUCCEEDED" in submit.stdout, detail
        # exactly one pod executed per agent (the kubelet claim-by-identity)
        for x in ("a", "b"):
            files = [f for f in os.listdir(tmp_path / f"logs-{x}")
                     if f.endswith(".log")]
            assert len(files) == 1, (x, files, detail)
        # cross-node day-2: ctl fetches the coordinator's log over the wire
        logs = subprocess.run(
            [sys.executable, "-m", "mpi_operator_tpu.opshell.ctl",
             "--store", f"http://127.0.0.1:{port}", "logs", "pi-sdk"],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert logs.returncode == 0, logs.stdout + logs.stderr + detail
        assert "pi is approximately 3.1" in logs.stdout
    finally:
        _reap(procs)


@pytest.mark.slow  # full stack / subprocess e2e
def test_agent_death_evicts_and_gang_restarts_on_survivor(tmp_path):
    """Kill one agent mid-job: the leader's NodeMonitor notices the silent
    heartbeat, evicts the dead node's pod (reason=Evicted — retryable), the
    controller drives its gang-coherent restart, and the scheduler re-places
    the whole gang on the surviving node. The job must still succeed."""
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.api.conditions import is_finished, is_succeeded
    from mpi_operator_tpu.machinery.http_store import HttpStoreClient

    port, procs = _start_cluster(tmp_path, node_grace=1.5, heartbeat=0.3)
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["agent-a", "agent-b"])
        client = TPUJobClient(store)
        client.create({
            "apiVersion": "tpujob.dev/v1",
            "kind": "TPUJob",
            "metadata": {"name": "survivor"},
            "spec": {
                "worker": {
                    "replicas": 2,
                    "template": {"containers": [{
                        "name": "w", "image": "local",
                        # gang-coupled workload: worker 0 fails like a
                        # collective when its peer's process dies
                        "command": ["python", "tests/data/coupled_worker.py"],
                        "env": [{"name": "HOLD_SECONDS", "value": "6"}],
                    }]},
                },
                "slice": {"accelerator": "cpu", "chipsPerHost": 1},
            },
        })
        # wait until both workers are actually running, one per agent
        deadline = time.time() + 90
        while time.time() < deadline:
            pods = store.list("Pod", "default",
                              selector={LABEL_JOB_NAME: "survivor"})
            if (len(pods) == 2
                    and all(p.status.phase == PodPhase.RUNNING for p in pods)):
                break
            time.sleep(0.3)
        else:
            raise TimeoutError(
                "pods never ran:\n"
                + _proc_logs(tmp_path, ["operator", "agent-a", "agent-b"]))
        assert {p.spec.node_name for p in pods} == {"agent-a", "agent-b"}
        # kill agent-b without cleanup: no drain mark, only silence
        agent_b = procs[2][0]
        agent_b.kill()
        final = client.wait("survivor", until=is_finished, timeout=120)
        detail = _proc_logs(tmp_path, ["operator", "agent-a", "agent-b"])
        assert is_succeeded(final.status), (final.status.conditions, detail)
        pods = store.list("Pod", "default", selector={LABEL_JOB_NAME: "survivor"})
        assert pods and all(p.spec.node_name == "agent-a" for p in pods), (
            [(p.metadata.name, p.spec.node_name) for p in pods], detail)
        assert any(e.reason == "NodeLost" for e in store.list("Event")), detail
        node_b = store.get("Node", NODE_NAMESPACE, "agent-b")
        assert node_b.status.ready is False
    finally:
        _reap(procs)


def test_ctl_nodes_lists_the_agent_fleet(tmp_path, capsys):
    """`ctl nodes` ≙ `kubectl get nodes`: the execution plane at a glance."""
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.opshell.ctl import cmd_nodes

    store = ObjectStore()
    make_node(store, "node-a", chips=4)
    make_node(store, "node-b", ready=False)
    pod = _bound_running_pod(store, "j", "node-a")
    assert pod is not None
    client = TPUJobClient(store)

    class A:
        pass

    assert cmd_nodes(client, A()) == 0
    out = capsys.readouterr().out
    assert "node-a" in out and "Ready" in out
    assert "node-b" in out and "NotReady" in out
    lines = [ln for ln in out.splitlines() if ln.startswith("node-a")]
    assert lines and " 4 " in lines[0] and " 1 " in lines[0]  # chips, pods


# ---------------------------------------------------------------------------
# node lifecycle verbs: cordon / uncordon / drain (≙ kubectl)
# ---------------------------------------------------------------------------


class _Args:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def test_cordoned_node_receives_no_bindings_until_uncordoned(capsys):
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.opshell.ctl import cmd_cordon, cmd_nodes, cmd_uncordon

    store = ObjectStore()
    sched = GangScheduler(store)
    make_node(store, "node-a")
    client = TPUJobClient(store)
    assert cmd_cordon(client, _Args(name="node-a")) == 0
    make_gang(store, "j", min_member=1)
    make_pod(store, "j", 0)
    sched.sync()
    assert bound_pods(store, "j") == []  # cordoned: zero schedulable targets
    assert cmd_nodes(client, _Args()) == 0
    assert "SchedulingDisabled" in capsys.readouterr().out
    assert cmd_uncordon(client, _Args(name="node-a")) == 0
    sched.sync()
    assert [p.spec.node_name for p in bound_pods(store, "j")] == ["node-a"]


def test_heartbeat_preserves_cordon_flag(tmp_path):
    """An agent's heartbeat rewrites its Node status; the cordon flag is the
    operator's and must survive every beat."""
    from mpi_operator_tpu.executor.agent import NodeAgent

    store = ObjectStore()
    agent = NodeAgent(store, "node-a", logs_dir=str(tmp_path))
    agent.log_server.start()
    agent.executor.log_url_base = "http://x/logs"
    agent._register()
    node = store.get("Node", NODE_NAMESPACE, "node-a")
    node.status.unschedulable = True
    store.update(node, force=True)
    agent._register()  # the heartbeat body
    node = store.get("Node", NODE_NAMESPACE, "node-a")
    assert node.status.unschedulable is True
    assert node.status.ready is True
    agent.log_server.stop()


def test_drain_evicts_pods_and_gang_lands_on_other_node():
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.opshell.ctl import cmd_drain

    store = ObjectStore()
    sched = GangScheduler(store)
    make_node(store, "node-a")
    make_node(store, "node-b")
    make_gang(store, "j", min_member=2)
    for i in range(2):
        make_pod(store, "j", i)
    sched.sync()
    bound = {p.metadata.name: p.spec.node_name for p in bound_pods(store, "j")}
    assert set(bound.values()) == {"node-a", "node-b"}
    for p in store.list("Pod"):
        p.status.phase = PodPhase.RUNNING
        store.update(p, force=True)
    client = TPUJobClient(store)
    # --now: the break-glass client-side path (no operator in this test);
    # the default graceful path only stamps the maintenance notice and
    # leaves evacuation to the DrainController (tests/test_disruption.py)
    assert cmd_drain(client, _Args(name="node-b", now=True)) == 0
    drained = store.get("Pod", "default", "j-worker-1")
    assert drained.is_evicted()  # → the controller's gang restart path
    # after the controller recreates the gang, rebinding avoids node-b:
    # simulate the recreate and resync
    for p in store.list("Pod"):
        store.delete("Pod", p.metadata.namespace, p.metadata.name)
    for i in range(2):
        make_pod(store, "j", i)
    sched.sync()
    assert all(
        p.spec.node_name == "node-a" for p in bound_pods(store, "j")
    ), [(p.metadata.name, p.spec.node_name) for p in bound_pods(store, "j")]


def test_monitor_bumps_node_metrics():
    from mpi_operator_tpu.opshell import metrics

    store = ObjectStore()
    make_node(store, "gone", hb=time.time() - 60)
    _bound_running_pod(store, "j", "gone")
    lost0 = metrics.nodes_lost.get()
    evicted0 = metrics.pods_evicted.get()
    NodeMonitor(store, grace=5.0).sync()
    assert metrics.nodes_lost.get() == lost0 + 1
    assert metrics.pods_evicted.get() == evicted0 + 1


def test_reaper_cannot_stamp_a_recreated_pod(tmp_path):
    """Incarnation guard: a gang restart deletes and recreates a same-name
    pod while the old process's reaper is still in flight; the reaper's
    exit status (rc=-9 from the _forget kill) must not land on the fresh
    incarnation — that would fail the restarted job with its predecessor's
    corpse (found live via `ctl drain`)."""
    from mpi_operator_tpu.api.types import Container, ObjectMeta
    from mpi_operator_tpu.executor.local import LocalExecutor
    from mpi_operator_tpu.machinery.objects import Pod, PodSpec

    store = ObjectStore()
    old = store.create(Pod(
        metadata=ObjectMeta(name="w-0", namespace="default"),
        spec=PodSpec(container=Container()),
    ))
    ex = LocalExecutor(store, logs_dir=str(tmp_path))
    # the restart: delete + recreate same-name (new uid)
    store.delete("Pod", "default", "w-0")
    fresh = store.create(Pod(
        metadata=ObjectMeta(name="w-0", namespace="default"),
        spec=PodSpec(container=Container()),
    ))
    assert fresh.metadata.uid != old.metadata.uid
    # the in-flight reaper stamps the OLD incarnation's failure
    ex._set_phase(old, PodPhase.FAILED, reason="ExitCode-9", exit_code=-9)
    cur = store.get("Pod", "default", "w-0")
    assert cur.status.phase == PodPhase.PENDING  # untouched
    # and the fresh incarnation's own updates still land
    ex._set_phase(fresh, PodPhase.RUNNING, ip="127.0.0.1")
    assert store.get("Pod", "default", "w-0").status.phase == PodPhase.RUNNING


def test_logs_follow_streams_incrementally(tmp_path, capsys):
    """`ctl logs --follow` ≙ kubectl logs -f: incremental byte-offset
    fetches from the agent's log endpoint, exiting when the pod finishes."""
    import threading

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.api.types import Container, ObjectMeta
    from mpi_operator_tpu.executor.agent import LogServer
    from mpi_operator_tpu.machinery.objects import Pod, PodSpec
    from mpi_operator_tpu.opshell.ctl import _follow_logs

    store = ObjectStore()
    logf = tmp_path / "w.log"
    logf.write_text("first line\n")
    srv = LogServer(str(tmp_path), host="127.0.0.1").start()
    try:
        pod = store.create(Pod(
            metadata=ObjectMeta(name="w-0", namespace="default"),
            spec=PodSpec(container=Container()),
        ))
        pod.status.phase = PodPhase.RUNNING
        pod.status.log_path = f"http://127.0.0.1:{srv.port}/logs/w.log"
        store.update(pod, force=True)

        def finish_later():
            time.sleep(1.2)
            with open(logf, "a") as f:
                f.write("second line\n")
            cur = store.get("Pod", "default", "w-0")
            cur.status.phase = PodPhase.SUCCEEDED
            store.update(cur, force=True)

        t = threading.Thread(target=finish_later)
        t.start()
        client = TPUJobClient(store)
        rc = _follow_logs(client, pod, pod.status.log_path)
        t.join()
        assert rc == 0
        out = capsys.readouterr().out
        assert "first line" in out and "second line" in out
        # incremental: the second fetch must not replay the first line
        assert out.count("first line") == 1
    finally:
        srv.stop()


@pytest.mark.slow  # full stack / subprocess e2e
def test_inventory_identity_agents_end_to_end(tmp_path):
    """Topology mode with real agents: the operator admits against a
    slice-shaped inventory (--inventory-slices), agents register under the
    inventory's node identities (slice0/0 — the '/' exercising URL quoting
    through store, scheduler, and agent claim), and a 2-worker SPMD job
    runs one pod per slice host."""
    import subprocess
    import sys

    from mpi_operator_tpu.machinery.http_store import HttpStoreClient
    from mpi_operator_tpu.runtime.emulation import free_port

    port = free_port()
    procs = []
    procs.append(_spawn(tmp_path, "operator", [
        sys.executable, "-m", "mpi_operator_tpu.opshell",
        "--store", f"sqlite:{tmp_path / 'store.db'}",
        "--serve-store", f"127.0.0.1:{port}",
        "--inventory-slices", "2",
        "--monitoring-port", "0",
    ]))
    _wait_http(f"http://127.0.0.1:{port}/healthz")
    for i in (0, 1):
        (tmp_path / f"logs-{i}").mkdir()
        procs.append(_spawn(tmp_path, f"agent-{i}", [
            sys.executable, "-m", "mpi_operator_tpu.executor.agent",
            "--store", f"http://127.0.0.1:{port}",
            "--node-name", f"slice0/{i}",
            "--logs-dir", str(tmp_path / f"logs-{i}"),
            "--workdir", REPO,
        ]))
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["slice0/0", "slice0/1"])
        submit = subprocess.run(
            [sys.executable, "examples/submit_job.py", f"http://127.0.0.1:{port}"],
            cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        detail = (submit.stdout + submit.stderr + "\n"
                  + _proc_logs(tmp_path, ["operator", "agent-0", "agent-1"]))
        assert submit.returncode == 0, detail
        assert "SUCCEEDED" in submit.stdout, detail
        # one pod per slice host, claimed by node identity
        for i in (0, 1):
            files = [f for f in os.listdir(tmp_path / f"logs-{i}")
                     if f.endswith(".log")]
            assert len(files) == 1, (i, files, detail)
        pods = store.list("Pod", "default", selector={LABEL_JOB_NAME: "pi-sdk"})
        assert {p.spec.node_name for p in pods} <= {"slice0/0", "slice0/1"}
    finally:
        _reap(procs)


def test_eviction_kills_the_running_process_and_keeps_the_marker(tmp_path):
    """Eviction means KILL (kubelet semantics): drain/monitor force a pod
    Failed while its process lives; the executor must kill it or the gang's
    collectives stay healthy and the drain never converges — and the
    reaper's rc=-9 must NOT overwrite the Evicted reason (terminal status
    is write-once), or the failure stops being retryable."""
    from mpi_operator_tpu.api.types import Container, ObjectMeta
    from mpi_operator_tpu.executor.local import LocalExecutor
    from mpi_operator_tpu.machinery.objects import Pod, PodSpec, evict_pod

    store = ObjectStore()
    ex = LocalExecutor(store, logs_dir=str(tmp_path))
    ex.start()
    try:
        store.create(Pod(
            metadata=ObjectMeta(name="w-0", namespace="default"),
            spec=PodSpec(container=Container(
                command=["python", "-c", "import time; time.sleep(60)"],
            )),
        ))
        deadline = time.time() + 20
        while time.time() < deadline:
            if store.get("Pod", "default", "w-0").status.phase == PodPhase.RUNNING:
                break
            time.sleep(0.05)
        proc = ex._procs["default/w-0"]
        assert proc.poll() is None
        assert evict_pod(store, store.get("Pod", "default", "w-0"),
                         "node drained")
        deadline = time.time() + 10
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.05)
        assert proc.poll() is not None, "evicted pod's process must be killed"
        time.sleep(0.5)  # give the reaper a chance to (wrongly) overwrite
        cur = store.get("Pod", "default", "w-0")
        assert cur.is_evicted(), (cur.status.reason, cur.status.exit_code)
    finally:
        ex.stop()


def test_log_server_chunks_large_files(tmp_path):
    """/logs responses are bounded (an unbounded read of a multi-GB log
    would OOM the agent and PDEATHSIG every worker on the node); clients
    loop on ?offset= — which cmd_logs does."""
    import urllib.request

    from mpi_operator_tpu.executor import agent as agent_mod

    big = tmp_path / "big.log"
    big.write_bytes(b"x" * (agent_mod.MAX_LOG_CHUNK + 1234))
    srv = agent_mod.LogServer(str(tmp_path), host="127.0.0.1").start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/logs/big.log", timeout=5
        ) as r:
            first = r.read()
        assert len(first) == agent_mod.MAX_LOG_CHUNK
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/logs/big.log?offset={len(first)}",
            timeout=5,
        ) as r:
            rest = r.read()
        assert len(rest) == 1234
    finally:
        srv.stop()


def test_scheduler_wakes_on_node_events():
    """An uncordon / new registration / returning heartbeat emits only Node
    events; pending gangs must re-sync without waiting for unrelated pod
    churn (and a periodic resync covers nodes going silently stale)."""
    store = ObjectStore()
    sched = GangScheduler(store)
    # node FIRST (agents register before jobs arrive): otherwise a sync
    # between pod- and node-creation would fall back to scalar 'local' mode
    make_node(store, "node-a", ready=False)  # registered but not ready
    sched.start()
    try:
        make_gang(store, "j", min_member=1)
        make_pod(store, "j", 0)
        time.sleep(0.5)
        assert bound_pods(store, "j") == []
        node = store.get("Node", NODE_NAMESPACE, "node-a")
        node.status.ready = True
        node.status.last_heartbeat = time.time()
        store.update(node, force=True)  # ONLY a Node event
        deadline = time.time() + 20  # generous: suite load can starve threads
        while time.time() < deadline and not bound_pods(store, "j"):
            time.sleep(0.1)
        assert [p.spec.node_name for p in bound_pods(store, "j")] == ["node-a"]
    finally:
        sched.stop()


def test_preemption_in_node_mode():
    """Preemption under node-capacity scheduling: the victim's chips free
    on its node and the critical gang binds there next pass."""
    from test_scheduler import job_pods, make_priority_gang

    store = ObjectStore()
    sched = GangScheduler(store, preemption_grace=0.0)
    make_node(store, "node-a", chips=2)
    make_priority_gang(store, "lowjob", 2, "low")
    for i in range(2):
        make_pod(store, "lowjob", i)
    sched.sync()
    assert len(bound_pods(store, "lowjob")) == 2
    make_priority_gang(store, "crit", 2, "critical")
    for i in range(2):
        make_pod(store, "crit", i)
    sched.sync()
    sched.sync()
    assert all(p.status.reason == "Preempted" for p in job_pods(store, "lowjob"))
    sched.sync()
    assert [p.spec.node_name for p in bound_pods(store, "crit")] == \
        ["node-a", "node-a"]


def _job_manifest(name, *, replicas, env, restart=None, backoff=None,
                  command=None, priority=None):
    spec = {
        "slice": {"accelerator": "cpu", "chips_per_host": 1},
        "worker": {
            "replicas": replicas,
            "template": {"containers": [{
                "name": "w", "image": "local",
                "command": command or ["python", "examples/llama_worker.py"],
                "env": [{"name": k, "value": v} for k, v in env.items()],
            }]},
        },
    }
    if restart:
        spec["worker"]["restart_policy"] = restart
    run_policy = {}
    if backoff is not None:
        run_policy["backoff_limit"] = backoff
    if priority is not None:
        run_policy["scheduling_policy"] = {"priority_class": priority}
    if run_policy:
        spec["run_policy"] = run_policy
    return {
        "apiVersion": "tpujob.dev/v1", "kind": "TPUJob",
        "metadata": {"name": name}, "spec": spec,
    }


def _wait_pods_running(store, job, n, deadline_s, tmp_path, tags):
    """Until exactly ``n`` pods of ``job`` are RUNNING; returns them."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        pods = [p for p in store.list("Pod")
                if p.metadata.labels.get(LABEL_JOB_NAME) == job
                and p.status.phase == PodPhase.RUNNING]
        if len(pods) == n:
            return pods
        time.sleep(0.5)
    raise TimeoutError(
        f"{job}: {n} RUNNING pods never appeared "
        f"(have {[(p.metadata.name, p.status.phase) for p in store.list('Pod') if p.metadata.labels.get(LABEL_JOB_NAME) == job]})\n"
        + _proc_logs(tmp_path, tags)
    )


def _wait_job(store, name, deadline_s, tmp_path, tags):
    from mpi_operator_tpu.api.conditions import is_failed, is_succeeded

    deadline = time.time() + deadline_s
    while time.time() < deadline:
        job = store.get("TPUJob", "default", name)
        if is_succeeded(job.status):
            return job
        assert not is_failed(job.status), (
            str(job.status.conditions) + "\n" + _proc_logs(tmp_path, tags)
        )
        time.sleep(1)
    raise TimeoutError(
        f"{name} never succeeded\n" + _proc_logs(tmp_path, tags)
    )


def _coordinator_report(store, job_name):
    """Worker-0's final JSON report, fetched over the agent log endpoint —
    the only way to read it without a shared log filesystem."""
    import json as _json

    pods = [p for p in store.list("Pod")
            if p.metadata.labels.get(LABEL_JOB_NAME) == job_name]
    w0 = [p for p in pods if p.metadata.name.endswith("worker-0")]
    assert w0 and w0[0].status.log_path.startswith("http://")
    with urllib.request.urlopen(w0[0].status.log_path, timeout=10) as r:
        body = r.read().decode()
    return _json.loads(body.strip().splitlines()[-1]), pods


@pytest.mark.slow  # full stack / subprocess e2e / jax compile
def test_llama_fsdp_trains_across_two_agents(tmp_path):
    """VERDICT r4 weak #1: the heaviest workload ever to cross a REAL agent
    boundary was pi (~1s of compute). This runs llama FSDP training through
    the full three-tier plane — store server + operator + two separate
    agent processes — with parameters sharded over the two cross-process
    hosts (the manifest's LLAMA_MESH=fsdp=2), i.e. the reference's core
    promise: controller-created workers on N machines running real training
    (mpi_job_controller.go:817-877)."""
    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.machinery.http_store import HttpStoreClient

    tags = ["operator", "agent-a", "agent-b"]
    port, procs = _start_cluster(tmp_path)
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["agent-a", "agent-b"])
        TPUJobClient(store).create(_job_manifest(
            "llama-fsdp", replicas=2,
            env={"LLAMA_CONFIG": "tiny", "LLAMA_BATCH": "2",
                 "LLAMA_SEQ": "32", "LLAMA_STEPS": "4",
                 "LLAMA_MESH": "fsdp=2"},
        ))
        _wait_job(store, "llama-fsdp", 420, tmp_path, tags)
        report, pods = _coordinator_report(store, "llama-fsdp")
        # one worker per agent: FSDP crossed a real node boundary
        assert {p.spec.node_name for p in pods} == {"agent-a", "agent-b"}, (
            [(p.metadata.name, p.spec.node_name) for p in pods])
        assert report["outcome"] == "done"
        assert report["hosts"] == 2
        assert report["mesh"] == "fsdp=2"  # the manifest's plan, sharded
        store.close()
    finally:
        _reap(procs)


@pytest.mark.slow  # full stack / subprocess e2e / jax compile
def test_elastic_rescale_with_checkpoint_across_agents(tmp_path):
    """The composed elastic loop ACROSS REAL AGENTS: a 3-worker llama job
    spread over two agents checkpoints onto the shared volume both agents
    advertise (--ckpt-dir — the PVC-at-the-same-mountPath property of a
    real cluster), is rescaled to 2 via `ctl scale` mid-run, exits 75,
    restarts re-placed across the agents, and resumes from the checkpoint.
    ≙ the reference's elastic Horovod flow
    (examples/horovod/tensorflow-mnist-elastic.yaml:20-27) on this stack."""
    import subprocess
    import sys

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.machinery.http_store import HttpStoreClient

    tags = ["operator", "agent-a", "agent-b"]
    shared = tmp_path / "shared-ckpt"
    shared.mkdir()
    port, procs = _start_cluster(tmp_path, ckpt_dir=shared)
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["agent-a", "agent-b"])
        # NO LLAMA_CKPT in the manifest: the per-job path is derived from
        # the agent-advertised volume (bootstrap.default_checkpoint_dir)
        TPUJobClient(store).create(_job_manifest(
            "llama-el", replicas=3, restart="ExitCode", backoff=4,
            env={"LLAMA_CONFIG": "tiny", "LLAMA_BATCH": "2",
                 "LLAMA_SEQ": "16", "LLAMA_STEPS": "120",
                 "LLAMA_STEP_SLEEP": "0.05"},
        ))
        job_ckpt = shared / "default" / "llama-el"
        deadline = time.time() + 420
        while time.time() < deadline:
            if job_ckpt.exists() and any(p.is_dir() for p in job_ckpt.iterdir()):
                break
            time.sleep(0.5)
        else:
            raise TimeoutError("no checkpoint appeared on the shared volume\n"
                               + _proc_logs(tmp_path, tags))
        # live rescale through the CLI (what kubectl scale would do)
        r = subprocess.run(
            [sys.executable, "-m", "mpi_operator_tpu.opshell.ctl",
             "--store", f"http://127.0.0.1:{port}",
             "scale", "llama-el", "--replicas", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr
        _wait_job(store, "llama-el", 420, tmp_path, tags)
        report, pods = _coordinator_report(store, "llama-el")
        live = [p for p in pods if not p.metadata.name.endswith("worker-2")]
        # the restarted gang is re-placed across BOTH agents
        assert {p.spec.node_name for p in live} == {"agent-a", "agent-b"}, (
            [(p.metadata.name, p.spec.node_name) for p in pods])
        assert report["hosts"] == 2  # resumed at the rescaled size
        assert report["outcome"] == "done"
        # the checkpoint it restored from predates the end of training:
        # progress actually carried across the restart
        saved = sorted(int(p.name) for p in job_ckpt.iterdir() if p.is_dir())
        assert saved and saved[0] < 120, saved
        store.close()
    finally:
        _reap(procs)


@pytest.mark.slow  # full stack / subprocess e2e / jax compile
def test_preemption_across_agents_end_to_end(tmp_path):
    """Preemption composed with the node-agent plane — and the victim is a
    CHECKPOINTING TRAINER, not a sleeper (VERDICT carryover): a low-priority
    llama gang fills both agents' capacity; a critical job arrives, waits
    out --preemption-grace, and the scheduler evicts the trainer off BOTH
    agents (whole-gang). Eviction is SIGTERM + grace (executor
    eviction_grace), which the elastic loop folds into a gang-uniform
    FORCE-CHECKPOINT before exiting — periodic saves are disabled
    (LLAMA_SAVE_EVERY huge), so the second incarnation reporting
    ``start_step > 0`` proves the SIGTERM checkpoint specifically landed.
    The critical job runs spread across the freed agents, and the victim
    then resumes from its saved step and completes — the Volcano reclaim
    semantics (mpi_job_controller.go:1215-1237) with real work preserved."""
    import json as _json

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.machinery.http_store import HttpStoreClient

    tags = ["operator", "agent-a", "agent-b"]
    shared = tmp_path / "shared-ckpt"
    shared.mkdir()
    # eviction grace well above the save cost: the SIGTERM checkpoint
    # (allgather sync + orbax save) must land even on a loaded CI host —
    # a backstop SIGKILL mid-save is the one nondeterminism in this test
    port, procs = _start_cluster(tmp_path, preemption_grace=2, agent_chips=1,
                                 ckpt_dir=shared, eviction_grace=30)
    try:
        store = HttpStoreClient(f"http://127.0.0.1:{port}")
        _wait_nodes_registered(store, ["agent-a", "agent-b"])
        client = TPUJobClient(store)
        client.create(_job_manifest(
            "victim", replicas=2, priority="low", restart="ExitCode",
            backoff=6,
            env={"LLAMA_CONFIG": "tiny", "LLAMA_BATCH": "2",
                 "LLAMA_SEQ": "16", "LLAMA_STEPS": "150",
                 "LLAMA_STEP_SLEEP": "0.05",
                 # the ONLY checkpoint this job can ever write is the
                 # SIGTERM-forced one: resumption proves the mechanism
                 "LLAMA_SAVE_EVERY": "100000",
                 "LLAMA_CHECK_EVERY": "2",
                 "LLAMA_PROGRESS_EVERY": "5"},
        ))
        pods = _wait_pods_running(store, "victim", 2, 240, tmp_path, tags)
        assert {p.spec.node_name for p in pods} == {"agent-a", "agent-b"}
        # preempt only once the trainer is demonstrably STEPPING (past
        # compile): a SIGTERM during compile would never reach the
        # gang-synchronized checkpoint point inside the grace window
        w0 = [p for p in pods if p.metadata.name.endswith("worker-0")][0]
        deadline = time.time() + 240
        while time.time() < deadline:
            with urllib.request.urlopen(w0.status.log_path, timeout=10) as r:
                if b"progress: batch" in r.read():
                    break
            time.sleep(0.5)
        else:
            raise TimeoutError("victim never started stepping\n"
                               + _proc_logs(tmp_path, tags))

        client.create(_job_manifest(
            "crit-pi", replicas=2, env={}, priority="critical",
            command=["python", "examples/pi_worker.py", "50000"],
        ))
        _wait_job(store, "crit-pi", 240, tmp_path, tags)
        pods = [p for p in store.list("Pod")
                if p.metadata.labels.get(LABEL_JOB_NAME) == "crit-pi"]
        # the critical gang ran spread across BOTH agents (the capacity the
        # victim was evicted from), its SPMD gang seeing 2 hosts
        assert {p.spec.node_name for p in pods} == {"agent-a", "agent-b"}
        w0 = [p for p in pods if p.metadata.name.endswith("worker-0")]
        assert w0 and w0[0].status.log_path.startswith("http://"), (
            [(p.metadata.name, p.status.log_path) for p in pods])
        with urllib.request.urlopen(w0[0].status.log_path, timeout=10) as r:
            assert "(2 hosts)" in r.read().decode()
        evs = [e for e in store.list("Event") if e.reason == "Preempted"]
        assert evs, "no Preempted event recorded"
        # the SIGTERM force-checkpoint is on the shared volume
        job_ckpt = shared / "default" / "victim"
        assert job_ckpt.exists() and any(
            p.is_dir() for p in job_ckpt.iterdir()
        ), "no forced checkpoint appeared\n" + _proc_logs(tmp_path, tags)
        # once capacity frees, the victim restarts and RESUMES: the second
        # incarnation runs from the forced checkpoint to completion
        final = _wait_job(store, "victim", 420, tmp_path, tags)
        assert final.status.restart_count == 0  # preemption restarts are free
        report, _ = _coordinator_report(store, "victim")
        assert report["outcome"] == "done", report
        assert report["step"] == 150, report
        assert report["start_step"] > 0, (
            "second incarnation started from scratch — the SIGTERM "
            f"force-checkpoint was lost: {report}\n"
            + _proc_logs(tmp_path, tags))
        store.close()
    finally:
        _reap(procs)


def test_require_nodes_evicts_running_local_orphans():
    """Upgrade scenario: a pre-upgrade single-host operator left pods
    RUNNING bound to 'local', then the deployment moved to node mode
    (--executor none + agents). No local executor exists there by
    construction, so the store's RUNNING is a lie — left alone the orphans
    would hold chip budget forever and block future gangs. The healer
    evicts them (retryable), freeing the capacity for re-placement."""
    store = ObjectStore()
    sched = GangScheduler(store, require_nodes=True)
    make_gang(store, "orphan", min_member=1)
    p = make_pod(store, "orphan", 0)
    p.spec.node_name = "local"
    p.status.phase = PodPhase.RUNNING
    store.update(p, force=True)
    make_node(store, "node-a", chips=1)
    # a fresh gang contends for the capacity the orphan is squatting on
    make_gang(store, "fresh", min_member=1)
    make_pod(store, "fresh", 0)
    sched.sync()
    cur = store.get("Pod", "default", "orphan-worker-0")
    assert cur.is_evicted(), (cur.status.phase, cur.status.reason)
    # the orphan no longer holds budget: the fresh gang places this pass
    assert [q.spec.node_name for q in bound_pods(store, "fresh")] == ["node-a"]


def test_preemption_prunes_useless_collateral_victims():
    """Minimal victim set, for real: the greedy accumulation walks victims
    lowest-priority-first, so it can pick up a tiny low gang whose node
    could never host the preemptor before reaching the one whose eviction
    actually makes room. The prune-back pass drops the useless collateral —
    no gang suffers a restart that buys nothing."""
    from test_scheduler import job_pods, make_priority_gang

    store = ObjectStore()
    sched = GangScheduler(store, preemption_grace=0.0)
    make_node(store, "node-1", chips=4)
    make_node(store, "node-2", chips=8)
    make_priority_gang(store, "tiny-low", 1, "low")        # priority -100
    make_pod(store, "tiny-low", 0, chips=2)                # lands on node-1
    make_priority_gang(store, "big-mid", 1, "-50")         # integer class
    make_pod(store, "big-mid", 0, chips=8)                 # fills node-2
    sched.sync()
    assert [p.spec.node_name for p in bound_pods(store, "tiny-low")] == ["node-1"]
    assert [p.spec.node_name for p in bound_pods(store, "big-mid")] == ["node-2"]
    make_priority_gang(store, "crit", 1, "critical")
    make_pod(store, "crit", 0, chips=8)  # only node-2 could ever host it
    sched.sync()
    sched.sync()
    # ONLY big-mid evicted: evicting tiny-low would contribute nothing
    # (node-1's capacity can never host the 8-chip preemptor)
    assert all(p.status.reason == "Preempted" for p in job_pods(store, "big-mid"))
    assert all(not p.is_finished() for p in job_pods(store, "tiny-low"))
    sched.sync()
    assert [p.spec.node_name for p in bound_pods(store, "crit")] == ["node-2"]


@pytest.mark.slow  # full stack / subprocess e2e
def test_real_agent_workflow_on_scoped_token(tmp_path):
    """The entire agent workflow — register, heartbeat, claim, execute,
    status-mirror, serve logs — runs on a NODE-scoped credential (no admin
    token on the execution node at all), while job-level powers stay
    admin-only. ≙ running kubelets on node-restricted credentials instead
    of cluster-admin."""
    import subprocess
    import sys

    from mpi_operator_tpu.machinery.http_store import HttpStoreClient
    from mpi_operator_tpu.machinery.store import Forbidden
    from mpi_operator_tpu.runtime.emulation import free_port

    adm = tmp_path / "admin-token"
    adm.write_text("admintok\n")
    agents_file = tmp_path / "agent-tokens"
    agents_file.write_text("agent-a:agenttok\n")
    agent_tok_file = tmp_path / "agent-a-token"
    agent_tok_file.write_text("agenttok\n")
    port = free_port()
    procs = []
    tags = ["store", "operator", "agent-a"]
    procs.append(_spawn(tmp_path, "store", [
        sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
        "--store", f"sqlite:{tmp_path / 'store.db'}",
        "--listen", f"127.0.0.1:{port}",
        "--token-file", str(adm),
        "--agent-tokens-file", str(agents_file),
    ]))
    try:
        _wait_http(f"http://127.0.0.1:{port}/healthz")
        procs.append(_spawn(tmp_path, "operator", [
            sys.executable, "-m", "mpi_operator_tpu.opshell",
            "--store", f"http://127.0.0.1:{port}",
            "--token-file", str(adm), "--monitoring-port", "0",
        ]))
        (tmp_path / "logs-a").mkdir()
        procs.append(_spawn(tmp_path, "agent-a", [
            sys.executable, "-m", "mpi_operator_tpu.executor.agent",
            "--store", f"http://127.0.0.1:{port}",
            "--token-file", str(agent_tok_file),  # the SCOPED credential
            "--node-name", "agent-a",
            "--logs-dir", str(tmp_path / "logs-a"), "--workdir", REPO,
        ]))
        admin_store = HttpStoreClient(f"http://127.0.0.1:{port}",
                                      token="admintok")
        _wait_nodes_registered(admin_store, ["agent-a"])

        from mpi_operator_tpu.api.client import TPUJobClient

        TPUJobClient(admin_store).create(_job_manifest(
            "scoped", replicas=1, env={},
            command=["python", "examples/pi_worker.py", "50000"],
        ))
        _wait_job(admin_store, "scoped", 180, tmp_path, tags)
        pods = [p for p in admin_store.list("Pod")
                if p.metadata.labels.get(LABEL_JOB_NAME) == "scoped"]
        assert pods and pods[0].spec.node_name == "agent-a"
        assert pods[0].status.phase == PodPhase.SUCCEEDED

        # the scoped token cannot do job-level things
        agent_store = HttpStoreClient(f"http://127.0.0.1:{port}",
                                      token="agenttok")
        with pytest.raises(Forbidden):
            agent_store.delete("TPUJob", "default", "scoped")
        agent_store.close()
        admin_store.close()
    finally:
        _reap(procs)


def test_agent_tick_is_one_batched_request_for_heartbeat_and_mirrors(tmp_path):
    """The O(pods)→O(1) write-path contract: one agent tick — Node
    heartbeat plus every dirty pod-status mirror — is ONE patch_batch
    call against the store, no GET legs, no per-pod requests. The cordon
    flag survives by construction (merge-patch never mentions it)."""
    from mpi_operator_tpu.executor.agent import NodeAgent

    class Counting:
        def __init__(self, backing):
            self._backing = backing
            self.calls = {"patch_batch": 0, "patch": 0, "get": 0,
                          "update": 0, "list": 0}

        def patch_batch(self, items):
            self.calls["patch_batch"] += 1
            return self._backing.patch_batch(items)

        def patch(self, *a, **kw):
            self.calls["patch"] += 1
            return self._backing.patch(*a, **kw)

        def get(self, *a, **kw):
            self.calls["get"] += 1
            return self._backing.get(*a, **kw)

        def update(self, *a, **kw):
            self.calls["update"] += 1
            return self._backing.update(*a, **kw)

        def __getattr__(self, name):
            return getattr(self._backing, name)

    backing = ObjectStore()
    store = Counting(backing)
    agent = NodeAgent(store, "node-a", logs_dir=str(tmp_path),
                      heartbeat_interval=3600.0)
    agent.log_server.start()
    agent._register()  # create path
    # the operator cordons the node; heartbeats must not touch the flag
    node = backing.get("Node", NODE_NAMESPACE, "node-a")
    node.status.unschedulable = True
    backing.update(node, force=True)
    # two pods this node runs, with dirty status mirrors (what the
    # executor enqueues through its status_sink)
    for i, name in enumerate(("w-0", "w-1")):
        pod = Pod(metadata=ObjectMeta(name=name, namespace="d"))
        pod.spec.node_name = "node-a"
        committed = backing.create(pod)
        agent.batcher.enqueue(
            "d", name, committed.metadata.uid,
            committed.metadata.resource_version,
            {"phase": PodPhase.RUNNING, "ready": True},
        )
    before = dict(store.calls)
    agent._tick()
    after = store.calls
    assert after["patch_batch"] - before["patch_batch"] == 1
    assert after["patch"] == before["patch"]      # no per-pod requests
    assert after["get"] == before["get"]          # no GET legs
    assert after["update"] == before["update"]    # no PUT loop
    node = backing.get("Node", NODE_NAMESPACE, "node-a")
    assert node.status.unschedulable is True      # cordon preserved
    assert node.status.ready is True
    assert node.status.last_heartbeat > 0
    for name in ("w-0", "w-1"):
        assert backing.get("Pod", "d", name).status.phase == PodPhase.RUNNING
    # steady state: a tick with nothing dirty is STILL one request
    before = dict(store.calls)
    agent._tick()
    assert store.calls["patch_batch"] - before["patch_batch"] == 1
    assert store.calls["patch"] == before["patch"]
    agent.log_server.stop()


def test_agent_tick_survives_store_outage_and_requeues_mirrors(tmp_path):
    """A failed batch request (store down past the client's retry window)
    must not LOSE the drained pod mirrors: they re-enqueue and the next
    tick delivers them (VERDICT r5 weak #2 — a store blip must not turn
    heartbeating agents into silent state droppers)."""
    from mpi_operator_tpu.executor.agent import NodeAgent

    class Flaky:
        def __init__(self, backing):
            self._backing = backing
            self.fail_next = False

        def patch_batch(self, items):
            if self.fail_next:
                self.fail_next = False
                raise ConnectionRefusedError("store down")
            return self._backing.patch_batch(items)

        def __getattr__(self, name):
            return getattr(self._backing, name)

    backing = ObjectStore()
    store = Flaky(backing)
    agent = NodeAgent(store, "node-a", logs_dir=str(tmp_path),
                      heartbeat_interval=3600.0)
    agent.log_server.start()
    agent._register()
    pod = Pod(metadata=ObjectMeta(name="w-0", namespace="d"))
    pod.spec.node_name = "node-a"
    committed = backing.create(pod)
    agent.batcher.enqueue(
        "d", "w-0", committed.metadata.uid,
        committed.metadata.resource_version,
        {"phase": PodPhase.SUCCEEDED, "ready": False, "exit_code": 0},
    )
    store.fail_next = True
    with pytest.raises(ConnectionRefusedError):
        agent._tick()
    assert backing.get("Pod", "d", "w-0").status.phase == PodPhase.PENDING
    agent._tick()  # store back: the requeued mirror lands
    got = backing.get("Pod", "d", "w-0")
    assert got.status.phase == PodPhase.SUCCEEDED and got.status.exit_code == 0
    agent.log_server.stop()


def test_agent_stop_flushes_pending_mirrors(tmp_path):
    """stop() kills the executor's processes; the reapers' terminal
    mirrors land in the batcher whose flusher is exiting — stop must
    drain them synchronously (the old direct-write path did this
    implicitly), or killed pods would sit RUNNING in the store until the
    monitor's heartbeat grace window expired."""
    from mpi_operator_tpu.executor.agent import NodeAgent

    store = ObjectStore()
    agent = NodeAgent(store, "node-a", logs_dir=str(tmp_path))
    agent.log_server.start()
    agent._register()
    pod = Pod(metadata=ObjectMeta(name="w-0", namespace="d"))
    pod.spec.node_name = "node-a"
    committed = store.create(pod)
    agent.batcher.enqueue(
        "d", "w-0", committed.metadata.uid,
        committed.metadata.resource_version,
        {"phase": PodPhase.FAILED, "ready": False, "reason": "Evicted",
         "message": "agent stopping"},
    )
    agent.stop()
    got = store.get("Pod", "d", "w-0")
    assert got.status.phase == PodPhase.FAILED
    assert got.status.reason == "Evicted"
    assert store.get("Node", NODE_NAMESPACE, "node-a").status.ready is False
