"""Native runtime tests: build libtpucoll + pi, run real multi-process gangs.

≙ the reference's pi smoke test (examples/pi/pi.yaml: 2 workers × 1 slot,
documented in examples/pi/README.md as THE acceptance check) — here it runs
in-suite instead of requiring a cluster."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
PI = os.path.join(NATIVE, "build", "pi")

pytestmark = [
    pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain"),
    # slow tier: compiles the native lib + runs real process gangs
    pytest.mark.slow,
]


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def _gang_env(rank: int, size: int, port: int):
    env = dict(os.environ)
    env.update(
        {
            "TPUJOB_NUM_HOSTS": str(size),
            "TPUJOB_HOST_ID": str(rank),
            "TPUJOB_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        }
    )
    return env


def _run_gang(argv, size: int, timeout: float = 60.0):
    from mpi_operator_tpu.runtime.emulation import free_port

    port = free_port()
    procs = [
        subprocess.Popen(
            argv,
            env=_gang_env(r, size, port),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for r in range(size)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, err
        outs.append(out)
    return outs


def test_pi_two_hosts():
    """The reference's documented smoke test: 2 workers, sum-reduce to 0."""
    outs = _run_gang([PI, "500000"], size=2)
    assert "pi is approximately 3.14" in outs[0]
    assert outs[1] == ""  # only host 0 prints


def test_pi_four_hosts():
    outs = _run_gang([PI, "200000"], size=4)
    assert "pi is approximately 3.1" in outs[0]
    assert "(4 hosts" in outs[0]


def test_python_binding_single_host():
    from mpi_operator_tpu.native import HostCollectives

    with HostCollectives() as hc:
        assert hc.size == 1 and hc.rank == 0
        # single-host collectives are identities
        assert hc.allreduce_sum([1.5, 2.5]) == [1.5, 2.5]
        assert hc.broadcast([7.0]) == [7.0]
        assert hc.allgather([1.0, 2.0]) == [1.0, 2.0]
        assert hc.reduce_scatter_sum([1.0, 2.0]) == [1.0, 2.0]
        hc.barrier()


def test_python_binding_gang():
    """3 python processes allreduce through the C runtime."""
    script = os.path.join(REPO, "tests", "data", "native_gang_worker.py")
    outs = _run_gang([sys.executable, script], size=3)
    # every host sees the allreduced sum 0+1+2=3 and rank-sum 3.0
    for r, out in enumerate(outs):
        assert "ALLREDUCE [3.0, 30.0]" in out
        assert "BROADCAST [42.5]" in out  # host 0's value won everywhere
        assert "ALLGATHER [0.0, 0.5, 1.0, 1.5, 2.0, 2.5]" in out
        # each rank sends [r, 1+r, 2+r]; summed = [3, 6, 9]; rank r keeps
        # chunk r of the scatter
        assert f"REDUCE_SCATTER [{3.0 * (r + 1)}]" in out
        assert "EMPTY [] [] [] []" in out  # zero-length collectives are legal
    assert "ROOT_REDUCE 3.0" in outs[0]


def test_verbs_gang_all_collectives():
    """Every native verb (allreduce/reduce/bcast/allgather/barrier) across a
    3-host gang, self-checked in C (verbs_test.cc prints VERBS OK per rank
    iff every value matched)."""
    outs = _run_gang([os.path.join(NATIVE, "build", "verbs_test")], size=3)
    for r, out in enumerate(outs):
        assert f"VERBS OK rank {r}/3" in out, outs


def test_verbs_single_host_identity():
    outs = _run_gang([os.path.join(NATIVE, "build", "verbs_test")], size=1)
    assert "VERBS OK rank 0/1" in outs[0]
