"""Pallas flash-attention kernel numerics.

Every kernel test pins ``interpret=True`` so CPU runs exercise the actual
kernel body (auto mode on non-TPU backends falls back to the XLA chunked
reference, which would compare the reference against itself)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.kernels import flash_attention
from mpi_operator_tpu.parallel.ring_attention import dense_attention

# slow tier: XLA compiles / subprocess gangs (see pytest.ini)
pytestmark = pytest.mark.slow


def _qkv(key, b=2, t=128, h=4, hkv=None, d=16, dtype=jnp.float32):
    hkv = h if hkv is None else hkv
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d), dtype),
        jax.random.normal(kk, (b, t, hkv, d), dtype),
        jax.random.normal(kv, (b, t, hkv, d), dtype),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32, interpret=True)
    want = dense_attention(q, k, v, causal=causal, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_gqa():
    q, k, v = _qkv(jax.random.PRNGKey(1), h=8, hkv=2)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    want = dense_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_uneven_blocks():
    # t not divisible by block sizes exercises the tail tiles
    q, k, v = _qkv(jax.random.PRNGKey(2), t=96)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
    want = dense_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_bfloat16():
    q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_gradients_match_dense():
    q, k, v = _qkv(jax.random.PRNGKey(4), t=64)

    def f_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True, block_q=32, block_k=32, interpret=True) ** 2)

    def f_dense(q_, k_, v_):
        return jnp.sum(
            dense_attention(q_, k_, v_, causal=True, scale=q.shape[-1] ** -0.5) ** 2
        )

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_gqa_uneven(causal):
    # GQA group-summed dk/dv + partial tail tiles through the backward kernels
    q, k, v = _qkv(jax.random.PRNGKey(7), t=96, h=8, hkv=2)

    def f_flash(q_, k_, v_):
        return jnp.sum(
            flash_attention(
                q_, k_, v_, causal=causal, block_q=64, block_k=64, interpret=True
            )
            ** 2
        )

    def f_dense(q_, k_, v_):
        return jnp.sum(
            dense_attention(q_, k_, v_, causal=causal, scale=q.shape[-1] ** -0.5)
            ** 2
        )

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_jit_compiles():
    q, k, v = _qkv(jax.random.PRNGKey(5), t=64)
    f = jax.jit(lambda *a: flash_attention(*a, causal=True, block_q=32, block_k=32, interpret=True))
    out = f(q, k, v)
    assert out.shape == q.shape


def test_auto_mode_falls_back_off_tpu():
    # interpret=None on a non-TPU backend must use the XLA chunked reference
    # (exact vs dense), never the interpreted kernel.
    if jax.default_backend() == "tpu":
        pytest.skip("auto mode uses the real kernel on TPU")
    q, k, v = _qkv(jax.random.PRNGKey(6))
    got = flash_attention(q, k, v, causal=True)
    want = dense_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_bhtd_layout_matches_bthd():
    # heads-major inputs skip the wrapper transposes but must be numerically
    # identical to the model-layout path
    q, k, v = _qkv(jax.random.PRNGKey(8), h=8, hkv=2)
    want = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    got = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=True, block_q=32, block_k=32, interpret=True, layout="bhtd",
    )
    np.testing.assert_allclose(
        np.asarray(got.transpose(0, 2, 1, 3)), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_bhtd_layout_sharded_mesh_with_tensor_axis():
    # the heads-major PartitionSpec puts the head axis in position 1 — a
    # wrong spec would shard the sequence dim and break GQA numerics
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "tensor"))
    q, k, v = _qkv(jax.random.PRNGKey(9), b=2, h=8, hkv=4)
    want = dense_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    got = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=True, block_q=32, block_k=32, interpret=True,
        mesh=mesh, layout="bhtd",
    )
    np.testing.assert_allclose(
        np.asarray(got.transpose(0, 2, 1, 3)), np.asarray(want), atol=2e-5, rtol=2e-5
    )
