"""Ordered event-sequence assertions (≙ the reference's watch-driven
eventChecker, /root/reference/v2/test/integration/main_test.go:116-178):
tests assert the exact ORDER of the user-facing audit trail, not just that
reasons exist. VERDICT r5 "missing" #3.

Events are totally ordered by the recorder's global counter (the suffix of
every Event name — timestamps can tie within a millisecond burst, the
counter cannot), which matches commit order for a single store.
"""

from typing import List, Optional, Sequence


def recorded_events(store, involved_names: Optional[Sequence[str]] = None,
                    namespace: Optional[str] = None) -> List:
    """Every Event in recorder order, optionally filtered to the objects
    named in ``involved_names`` (job + its podgroup, say — one lifecycle's
    trail spans several involved objects)."""
    evs = store.list("Event", namespace)
    if involved_names is not None:
        wanted = set(involved_names)
        evs = [e for e in evs if e.involved.name in wanted]
    evs.sort(key=lambda e: int(e.metadata.name.rsplit(".", 1)[1]))
    return evs


def assert_event_sequence(store, expected_reasons: Sequence[str],
                          involved_names: Optional[Sequence[str]] = None,
                          namespace: Optional[str] = None) -> None:
    """Assert ``expected_reasons`` appear as an ordered SUBSEQUENCE of the
    recorded trail (extra events in between are fine — retries and
    warnings are part of a live system; reordering is not)."""
    reasons = [e.reason for e in recorded_events(store, involved_names, namespace)]
    it = iter(reasons)
    missing = [want for want in expected_reasons
               if not any(got == want for got in it)]
    assert not missing, (
        f"event sequence broken: {missing[0]!r} missing (or out of order) "
        f"in {reasons}"
    )
