"""tpujobctl: the kubectl-equivalent CLI (opshell/ctl.py).

≙ the reference's documented day-2 flow (/root/reference/examples/pi/
README.md): create -f, get, describe (with the Events audit trail),
delete — here against the framework's own store backends. The fixture runs
a real operator stack (controller + gang scheduler + local executor) on a
shared sqlite store; every CLI invocation is a separate store handle, the
same process split as a real deployment.
"""

import json
import os
import time

import pytest

from mpi_operator_tpu.controller.controller import (
    ControllerOptions,
    TPUJobController,
)
from mpi_operator_tpu.executor import LocalExecutor
from mpi_operator_tpu.machinery.events import EventRecorder
from mpi_operator_tpu.machinery.sqlite_store import SqliteStore
from mpi_operator_tpu.opshell import ctl
from mpi_operator_tpu.scheduler import GangScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PI_YAML = os.path.join(REPO, "examples", "pi.yaml")


@pytest.fixture
def stack(tmp_path):
    """Operator stack on a shared sqlite store; yields the store spec."""
    path = str(tmp_path / "ctl.db")
    store = SqliteStore(path, poll_interval=0.02)
    recorder = EventRecorder(store)
    controller = TPUJobController(store, recorder, ControllerOptions())
    scheduler = GangScheduler(store, recorder)
    executor = LocalExecutor(store, workdir=REPO, require_binding=True)
    controller.run()
    scheduler.start()
    executor.start()
    yield f"sqlite:{path}"
    executor.stop()
    scheduler.stop()
    controller.stop()
    store.close()


def run_ctl(store_spec, *argv):
    return ctl.main(["--store", store_spec, *argv])


@pytest.mark.slow  # full stack / subprocess e2e
def test_create_watch_get_describe_events_delete(stack, capsys):
    """The full kubectl-style session against a running operator."""
    assert run_ctl(stack, "create", "-f", PI_YAML) == 0
    assert "created" in capsys.readouterr().out

    # watch streams transitions and exits 0 on success
    assert run_ctl(stack, "watch", "pi", "--timeout", "120") == 0
    out = capsys.readouterr().out
    assert "Succeeded" in out

    assert run_ctl(stack, "get") == 0
    out = capsys.readouterr().out
    assert "NAME" in out and "pi" in out and "Succeeded" in out

    assert run_ctl(stack, "get", "pi", "-o", "json") == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["metadata"]["name"] == "pi"
    assert doc["kind"] == "TPUJob"

    assert run_ctl(stack, "describe", "pi") == 0
    out = capsys.readouterr().out
    assert "State:      Succeeded" in out
    assert "Conditions:" in out and "Events:" in out
    assert "TPUJobCreated" in out  # the audit trail is populated

    assert run_ctl(stack, "events", "pi") == 0
    out = capsys.readouterr().out
    assert "TPUJobSucceeded" in out

    # logs: job name resolves to the coordinator pod (≙ kubectl logs
    # pi-launcher, the reference README's way to read the result)
    assert run_ctl(stack, "logs", "pi") == 0
    assert "pi is approximately 3.1" in capsys.readouterr().out
    # ...and a pod name works directly
    assert run_ctl(stack, "logs", "pi-worker-1") == 0
    capsys.readouterr()
    assert run_ctl(stack, "logs", "no-such-thing") == 1
    assert "error" in capsys.readouterr().err

    assert run_ctl(stack, "delete", "pi") == 0
    assert "deleted" in capsys.readouterr().out
    assert run_ctl(stack, "get", "pi") == 1  # gone


def test_errors_and_admission(stack, tmp_path, capsys):
    # unknown job
    assert run_ctl(stack, "describe", "nope") == 1
    assert "error" in capsys.readouterr().err
    # strict schema: typo'd field rejected at create
    bad = tmp_path / "bad.yaml"
    bad.write_text(
        "apiVersion: tpujob.dev/v1\nkind: TPUJob\n"
        "metadata: {name: bad}\n"
        "spec:\n  worker:\n    replicaz: 2\n"
    )
    assert run_ctl(stack, "create", "-f", str(bad)) == 1
    assert "error" in capsys.readouterr().err
    # missing manifest file: clean error, not a traceback
    assert run_ctl(stack, "create", "-f", str(tmp_path / "nope.yaml")) == 1
    assert "error" in capsys.readouterr().err
    # duplicate create (rerunning the README command): clean error
    assert run_ctl(stack, "create", "-f", PI_YAML) == 0
    capsys.readouterr()
    assert run_ctl(stack, "create", "-f", PI_YAML) == 1
    assert "already exists" in capsys.readouterr().err


@pytest.mark.slow  # full stack / subprocess e2e
def test_suspend_scale_resume_lifecycle(stack, tmp_path, capsys):
    """kubectl-style day-2 mutation verbs on a live job: a job created
    suspended holds with no pods; `scale` changes the gang size while held
    (invalid sizes rejected by admission); `resume` releases it and the job
    runs at the new size."""
    import yaml

    with open(PI_YAML) as f:
        doc = yaml.safe_load(f)
    doc["metadata"]["name"] = "pi-held"
    doc["spec"].setdefault("runPolicy", {})["suspend"] = True
    manifest = tmp_path / "held.yaml"
    manifest.write_text(yaml.safe_dump(doc))

    assert run_ctl(stack, "create", "-f", str(manifest)) == 0
    capsys.readouterr()
    deadline = time.time() + 30
    while time.time() < deadline:
        run_ctl(stack, "get", "pi-held")
        if "Suspended" in capsys.readouterr().out:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("job never reached Suspended")

    assert run_ctl(stack, "scale", "pi-held", "--replicas", "0") == 1
    assert "error" in capsys.readouterr().err  # admission rejects 0 workers
    assert run_ctl(stack, "scale", "pi-held", "--replicas", "3") == 0
    assert "scaled to 3" in capsys.readouterr().out

    assert run_ctl(stack, "resume", "pi-held") == 0
    capsys.readouterr()
    assert run_ctl(stack, "watch", "pi-held", "--timeout", "120") == 0
    assert "Succeeded" in capsys.readouterr().out
    assert run_ctl(stack, "logs", "pi-held") == 0
    assert "(3 hosts" in capsys.readouterr().out  # ran at the scaled size

    # suspend works in the other direction too (spec round-trips)
    assert run_ctl(stack, "suspend", "pi-held") == 0
    assert "suspended" in capsys.readouterr().out


def test_memory_store_rejected(capsys):
    """A client CLI on a private in-process store would silently no-op."""
    assert ctl.main(["--store", "memory", "get"]) == 2
    assert "not usable" in capsys.readouterr().err


def test_job_state_precedence():
    """STATE column precedence mirrors the condition machine."""
    from mpi_operator_tpu.api.types import Condition, JobStatus, TPUJob

    job = TPUJob()
    assert ctl.job_state(job) == "Pending"
    job.status = JobStatus(conditions=[Condition(type="Created", status=True)])
    assert ctl.job_state(job) == "Created"
    job.status.conditions.append(Condition(type="Running", status=True))
    assert ctl.job_state(job) == "Running"
    job.status.conditions.append(Condition(type="Restarting", status=True))
    assert ctl.job_state(job) == "Restarting"
    job.status.conditions.append(Condition(type="Succeeded", status=True))
    assert ctl.job_state(job) == "Succeeded"


def test_admin_token_never_crosses_a_plaintext_log_connection(capsys):
    """The VERDICT's credential-leak finding, closed: `ctl logs` against an
    agent's PLAIN-HTTP log endpoint must never put the admin bearer token
    on the wire — the read token (downscoped) is sent instead, and with
    only an admin token in hand the fetch fails closed with a hint rather
    than leaking the cluster key. A capture server plays the agent and
    records every Authorization header that actually crossed the
    connection."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.api.types import ObjectMeta
    from mpi_operator_tpu.machinery.objects import Pod, PodPhase
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.opshell.ctl import cmd_logs, log_token_for

    seen_auth = []

    class Capture(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            import urllib.parse as up

            seen_auth.append(self.headers.get("Authorization"))
            if self.headers.get("Authorization") == "Bearer readtok":
                qs = up.parse_qs(up.urlparse(self.path).query)
                offset = int(qs.get("offset", ["0"])[0])
                body = b"hello from the worker"[offset:]  # the agent's
                # ?offset= contract: an empty tail ends the client's loop
                self.send_response(200)
            else:
                body = b"unauthorized"
                self.send_response(401)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Capture)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/logs/w.log"

    store = ObjectStore()
    pod = Pod(metadata=ObjectMeta(name="w", namespace="default"))
    pod.status.phase = PodPhase.SUCCEEDED
    pod.status.log_path = url
    store.create(pod)
    client = TPUJobClient(store)

    class Args:
        name = "w"
        stderr = False
        follow = False

    try:
        # admin token only: nothing is sent, the fetch 401s with a hint
        args = Args()
        args.log_admin_token = "admintok"
        args.log_read_token = None
        assert cmd_logs(client, args) == 1
        err = capsys.readouterr().err
        assert "refusing to send the admin token over plain HTTP" in err
        assert "--read-token-file" in err
        # read token present: the DOWNSCOPED credential is sent and works
        args = Args()
        args.log_admin_token = "admintok"
        args.log_read_token = "readtok"
        assert cmd_logs(client, args) == 0
        assert "hello from the worker" in capsys.readouterr().out
        # the wire never saw the admin secret, in any request (the read
        # path fetches twice: the body, then the empty ?offset= tail)
        assert seen_auth == [None, "Bearer readtok", "Bearer readtok"]
        assert all(a is None or "admintok" not in a for a in seen_auth)
    finally:
        httpd.shutdown()
        httpd.server_close()
    # the policy itself: admin rides TLS only; read is always preferred
    assert log_token_for("https://x/logs/a", admin="adm", read=None) == "adm"
    assert log_token_for("http://x/logs/a", admin="adm", read=None) is None
    assert log_token_for("https://x/logs/a", admin="adm", read="rd") == "rd"
    assert log_token_for("/var/log/a.log", admin="adm", read=None) is None


def test_events_churn_hint_points_at_convcheck(tmp_path, capsys):
    """A reason repeating with VARYING messages defeats the recorder's
    (reason, message) dedupe — the oscillation smell the convergence
    checker reproduces offline. `ctl events` must flag it on stderr
    without disturbing the table; a quiet trail gets no note."""
    from mpi_operator_tpu.machinery.events import WARNING

    path = str(tmp_path / "hint.db")
    store = SqliteStore(path, poll_interval=0.02)
    try:
        recorder = EventRecorder(store)
        spec = f"sqlite:{path}"
        assert run_ctl(spec, "create", "-f", PI_YAML) == 0
        capsys.readouterr()
        job = store.get("TPUJob", "default", "pi")

        # a quiet trail: a few distinct messages under one reason is normal
        for i in range(3):
            recorder.event(job, WARNING, "SchedulingParked", f"parked #{i}")
        assert run_ctl(spec, "events", "pi") == 0
        cap = capsys.readouterr()
        assert "SchedulingParked" in cap.out
        assert "oscillating" not in cap.err

        # churn: the same reason keeps re-deciding with fresh messages
        for i in range(3, 8):
            recorder.event(job, WARNING, "SchedulingParked", f"parked #{i}")
        assert run_ctl(spec, "events", "pi") == 0
        cap = capsys.readouterr()
        assert "oscillating" in cap.err
        assert "SchedulingParked" in cap.err
        assert "analysis converge" in cap.err
    finally:
        store.close()
