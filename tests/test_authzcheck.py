"""authzcheck: the declarative authorization matrix probed against a
real booted store fleet (ISSUE 20).

Tier-1 runs the loader's fail-closed contracts, the denied-cell probe on
the memory backing, cross-backend denied parity, the undeclared-route
injection, a representative mutant pair, the ops-plane wire-capture
secret scan, and the two regressions the first probe found (the peer
401/403 split and /v1/replica/status staying open under --auth-reads).
The exhaustive bar — full matrix clean on BOTH backings, all six
mutants caught with deterministic replays — is ``authz --selftest`` and
rides the slow tier plus the verify gate.
"""

import json
import os
import subprocess
import sys

import pytest

from mpi_operator_tpu.analysis import authzcheck
from mpi_operator_tpu.analysis.authzcheck import (
    AuthzConfigError,
    Probe,
    _fire,
    encode_token,
    parse_token,
)

pytestmark = pytest.mark.authz

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(*args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "mpi_operator_tpu.analysis", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


@pytest.fixture(scope="module")
def fleet():
    f = authzcheck.make_fleet("memory")
    yield f
    f.close()


# ---------------------------------------------------------------------------
# the loader fails closed
# ---------------------------------------------------------------------------


def _canonical_doc():
    with open(authzcheck.DEFAULT_POLICY_PATH, encoding="utf-8") as f:
        return json.load(f)


def _load_mutated(tmp_path, mutate):
    doc = _canonical_doc()
    mutate(doc)
    p = tmp_path / "policy.json"
    p.write_text(json.dumps(doc))
    return authzcheck.load_policy(str(p))


def test_canonical_policy_loads():
    policy = authzcheck.load_policy()
    assert policy.version == 1
    # every servable route is declared — the probe's coverage direction
    assert authzcheck.coverage_findings(policy) == []


@pytest.mark.parametrize(
    "mutate, match",
    [
        (lambda d: d.update(extra=1), "unknown top-level"),
        (lambda d: d.update(version=2), "not 1"),
        (lambda d: d["routes"]["GET /healthz"].update(superuser="allow"),
         "unknown tier"),
        (lambda d: d["routes"]["GET /healthz"].pop("admin"),
         "missing tier"),
        (lambda d: d["routes"]["GET /healthz"].update(admin="deny:9xx"),
         "grammar"),
        (lambda d: d["routes"]["POST /v1/objects"].update(
            admin={"default": "allow"}), "variants"),
        (lambda d: d["routes"].update({"GET /v1/nonexistent": "allow"}),
         "does not serve"),
        (lambda d: d["ops_server"].pop("GET /metrics"),
         "ops_server"),
    ],
    ids=["unknown-top-key", "bad-version", "unknown-tier", "missing-tier",
         "bad-outcome", "variant-mismatch", "non-servable-route",
         "missing-ops-route"],
)
def test_loader_fails_closed(tmp_path, mutate, match):
    with pytest.raises(AuthzConfigError, match=match):
        _load_mutated(tmp_path, mutate)


def test_loader_refuses_duplicate_keys(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"version": 1, "version": 1}')
    with pytest.raises(AuthzConfigError, match="duplicate key"):
        authzcheck.load_policy(str(p))


def test_undeclared_servable_route_is_a_finding():
    # a NEW endpoint the router serves but the matrix does not declare
    # must surface as a finding, not load-fail (the policy file stays
    # loadable so the gap can be reported) — the injection self_test and
    # the ISSUE acceptance both ride this seam
    injected = "GET /v1/debug-dump"
    servable = authzcheck.servable_routes() + [injected]
    policy = authzcheck.load_policy(servable=servable)
    findings = authzcheck.coverage_findings(policy, servable)
    assert [f.token for f in findings] == [
        encode_token(injected, "*", "undeclared")
    ]
    assert "no entry" in findings[0].message


# ---------------------------------------------------------------------------
# replay tokens
# ---------------------------------------------------------------------------


def test_token_round_trip():
    route = "PUT /v1/objects/{kind}/{ns}/{name}"
    tok = encode_token(route, "node", "cordon_flip")
    assert tok == f"v1:authz:{route}:node:cordon_flip"
    assert parse_token(tok) == (route, "node", "cordon_flip")


@pytest.mark.parametrize("bad", [
    "v2:authz:GET /x:anon:default",   # wrong prefix
    "v1:authz:GET/x:anon:default",    # no space → not a METHOD /route
    "v1:authz:GET /x:anon",           # too few fields
    "v1:authz:::",                    # empty fields
])
def test_bad_tokens_are_refused(bad):
    with pytest.raises(AuthzConfigError):
        parse_token(bad)


def test_replay_refuses_undeclared_cell():
    with pytest.raises(AuthzConfigError, match="no declared matrix cell"):
        authzcheck.replay("v1:authz:GET /healthz:anon:no_such_variant")


# ---------------------------------------------------------------------------
# the denied set probes clean, identically on both backings (tier-1's
# reduced state-preserving slice of the full-matrix selftest bar)
# ---------------------------------------------------------------------------


def test_denied_cells_probe_clean_and_backends_agree():
    mem = authzcheck.probe("memory", denied_only=True)
    assert mem.ok, mem.render()
    sql = authzcheck.probe("sqlite", denied_only=True)
    assert sql.ok, sql.render()
    # parity: every denied cell observes the SAME (status, typed error)
    # on both backings — authorization must not depend on the backing
    assert set(mem.observed) == set(sql.observed)
    diverged = {
        tok: (mem.observed[tok], sql.observed[tok])
        for tok in mem.observed if mem.observed[tok] != sql.observed[tok]
    }
    assert diverged == {}


# ---------------------------------------------------------------------------
# mutants (tier-1 pair: a tier-gate drop and a scope-check drop; the
# full six + deterministic replays ride --selftest in the slow tier)
# ---------------------------------------------------------------------------


def test_mutant_read_token_accepting_mutations_is_caught():
    mutant = "read-token-accepts-mutation"
    expected = authzcheck.MUTANTS[mutant].token
    report = authzcheck.probe("memory", mutant=mutant, denied_only=True)
    assert not report.ok
    assert expected in {f.token for f in report.findings}, report.render()
    # the token replays the exact diff deterministically, and the same
    # cell probes clean on an unmutated fleet
    first = authzcheck.replay(expected, mutant=mutant)
    second = authzcheck.replay(expected, mutant=mutant)
    assert first is not None and first == second
    assert authzcheck.replay(expected) is None


def test_mutant_cordon_key_denial_dropped_is_caught():
    mutant = "cordon-key-denial-dropped"
    expected = authzcheck.MUTANTS[mutant].token
    report = authzcheck.probe("memory", mutant=mutant, denied_only=True)
    assert not report.ok
    assert expected in {f.token for f in report.findings}, report.render()


# ---------------------------------------------------------------------------
# ops-plane posture: deliberately open, but no secret rides it
# ---------------------------------------------------------------------------


def test_exposition_secret_scan():
    assert authzcheck.scan_exposition(
        'cp_jobs_total{phase="Running"} 3\n'
    ) == []
    leak = authzcheck.scan_exposition('cp_info{peer_token="s3cr3t"} 1\n')
    assert leak and "peer_token" in leak[0]
    # values are never echoed into the violation messages
    assert "s3cr3t" not in " ".join(leak)


def test_ops_metrics_open_and_secret_free(fleet):
    obs = _fire(fleet, Probe("ops", "GET", "/metrics", None, None))
    assert obs.status == 200
    from urllib.request import urlopen

    with urlopen(fleet.url("ops") + "/metrics", timeout=10.0) as resp:
        body = resp.read().decode("utf-8", "replace")
    assert authzcheck.scan_exposition(body) == []
    for tok in authzcheck._FLEET_TOKENS.values():
        assert tok is None or tok not in body


# ---------------------------------------------------------------------------
# regressions the first probe found (fixed, not allowlisted)
# ---------------------------------------------------------------------------


def test_peer_routes_split_401_vs_403(fleet):
    # missing/unrecognized credentials are AUTHENTICATION failures: 401
    for bearer in (None, "not-a-real-token"):
        obs = _fire(fleet, Probe(
            "main", "POST", "/v1/replica/fetch-entries", {"args": [0, 1]},
            bearer,
        ))
        assert (obs.status, obs.error) == (401, "Unauthorized"), obs
    # a VALID token of the wrong tier is an AUTHORIZATION failure: 403
    for tier in ("admin", "read", "node"):
        obs = _fire(fleet, Probe(
            "main", "POST", "/v1/replica/fetch-entries", {"args": [0, 1]},
            authzcheck._FLEET_TOKENS[tier],
        ))
        assert (obs.status, obs.error) == (403, "Forbidden"), (tier, obs)


def test_replica_status_and_healthz_stay_open_under_auth_reads(fleet):
    # the main fleet server runs --auth-reads; liveness and role probes
    # carry no credentials and must stay open regardless
    for path in ("/healthz", "/v1/replica/status"):
        obs = _fire(fleet, Probe("main", "GET", path, None, None))
        assert obs.status == 200, (path, obs)


def test_cli_replay_bad_token_fails_closed():
    res = _run_cli("authz", "--replay", "not-a-token")
    assert res.returncode == 2
    assert "v1:authz:" in res.stderr


# ---------------------------------------------------------------------------
# the exhaustive bar (slow tier; also the verify gate's static check)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_selftest_full_bar():
    assert authzcheck.self_test() == []
